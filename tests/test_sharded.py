"""Sharded factor serving (ISSUE 9) — ``pio deploy --shard-factors``.

The parity CI guard: sharded-vs-replicated ALS factors and top-K ids
must be comparable at a small catalog on the 1×8 host mesh (scores
within tolerance, ids tie-stable), sharding strictly opt-in, the
``/reload`` hot-swap must drop the previous generation's shard handles
on EVERY device, and per-device memory must follow the
``catalog / model_axis`` model the whole PR exists for.
"""

from __future__ import annotations

import gc
import weakref

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.data.aggregator import BiMap
from predictionio_tpu.ops.als import ALSConfig, top_k_items_batch, train_als
from predictionio_tpu.parallel import sharding
from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModel,
    Query,
)


def _factors(U=70, I=130, K=8, seed=3):
    rng = np.random.default_rng(seed)
    uf = rng.standard_normal((U, K)).astype(np.float32)
    vf = rng.standard_normal((I, K)).astype(np.float32)
    return uf, vf


def _model(uf, vf) -> ALSModel:
    U, I = uf.shape[0], vf.shape[0]
    return ALSModel(
        uf.copy(),
        vf.copy(),
        BiMap.string_index([f"u{i}" for i in range(U)]),
        BiMap.string_index([f"i{i}" for i in range(I)]),
    )


# ---------------------------------------------------------------------------
# Kernel-level parity
# ---------------------------------------------------------------------------


class TestShardTable:
    def test_padding_and_placement(self):
        mesh = sharding.serving_mesh()
        assert mesh is not None and mesh.shape["model"] == 8
        uf, _ = _factors(U=61)
        tbl = sharding.shard_table(uf, mesh)
        assert tbl.shape == (64, uf.shape[1])  # padded to a multiple of 8
        # every device holds exactly one [8, K] shard — the memory model
        assert sharding.per_device_bytes(tbl) == 8 * uf.shape[1] * 4
        host = np.asarray(tbl)
        np.testing.assert_array_equal(host[:61], uf)
        np.testing.assert_array_equal(host[61:], 0.0)

    def test_byte_math_matches_measured(self):
        mesh = sharding.serving_mesh()
        uf, _ = _factors(U=100, K=16)
        tbl = sharding.shard_table(uf, mesh)
        assert sharding.per_device_bytes(tbl) == sharding.sharded_table_bytes(
            100, 16, 8
        )
        # the OOM-shape regression is pure shape math: the BENCH_r01
        # table cannot fit replicated, its 8-way shard must
        hbm = 17 * 2**30
        assert 2 * sharding.table_bytes(64_761_856, 64) > hbm
        assert 2 * sharding.sharded_table_bytes(64_761_856, 64, 8) < hbm

    def test_serving_mesh_caps_and_single_device(self):
        assert sharding.serving_mesh(shards=1) is None
        m2 = sharding.serving_mesh(shards=2)
        assert m2 is not None and m2.shape["model"] == 2


class TestShardedTopK:
    def test_ids_and_scores_match_replicated_exact(self):
        mesh = sharding.serving_mesh()
        uf, vf = _factors()
        ut, it = sharding.shard_table(uf, mesh), sharding.shard_table(vf, mesh)
        rng = np.random.default_rng(0)
        idx = rng.integers(0, uf.shape[0], 48).astype(np.int32)
        for k in (1, 5, 16):
            ids_s, sc_s = sharding.sharded_topk_users(
                idx, ut, it, k, vf.shape[0], mesh
            )
            ids_r, sc_r = top_k_items_batch(
                jnp.asarray(idx), jnp.asarray(uf), jnp.asarray(vf), k
            )
            np.testing.assert_array_equal(np.asarray(ids_s), np.asarray(ids_r))
            np.testing.assert_allclose(
                np.asarray(sc_s), np.asarray(sc_r), rtol=1e-6
            )

    def test_tie_stability_across_shard_boundaries(self):
        """Duplicate item rows land on DIFFERENT shards (ids 3, 77, 120
        of 130 items over 8 shards) yet must merge in ascending-id order
        exactly like the replicated kernel."""
        mesh = sharding.serving_mesh()
        uf, vf = _factors()
        vf[3] = vf[120]
        vf[77] = vf[120]
        uf[0] = vf[120]  # query aligned with the tied rows
        ut, it = sharding.shard_table(uf, mesh), sharding.shard_table(vf, mesh)
        idx = np.zeros(4, np.int32)
        ids_s, _ = sharding.sharded_topk_users(idx, ut, it, 6, vf.shape[0], mesh)
        ids_r, _ = top_k_items_batch(
            jnp.asarray(idx), jnp.asarray(uf), jnp.asarray(vf), 6
        )
        np.testing.assert_array_equal(np.asarray(ids_s), np.asarray(ids_r))
        assert {3, 77, 120} <= set(np.asarray(ids_s)[0].tolist())

    def test_padding_rows_never_rank(self):
        """Zero padding rows would outrank real negative scores if the
        num_items mask slipped — force an all-negative score row."""
        mesh = sharding.serving_mesh()
        uf, vf = _factors(U=8, I=13)
        uf[0] = 1.0
        vf[:] = -np.abs(vf)  # every real score strictly negative
        ut, it = sharding.shard_table(uf, mesh), sharding.shard_table(vf, mesh)
        ids_s, sc_s = sharding.sharded_topk_users(
            np.zeros(1, np.int32), ut, it, 13, 13, mesh
        )
        assert np.asarray(ids_s).max() < 13
        assert np.asarray(sc_s).max() < 0

    def test_gather_rows_resolves_across_shards(self):
        mesh = sharding.serving_mesh()
        uf, _ = _factors(U=37)
        ut = sharding.shard_table(uf, mesh)
        idx = np.asarray([0, 8, 17, 36], np.int32)
        np.testing.assert_array_equal(
            np.asarray(sharding.gather_rows(idx, ut, mesh)), uf[idx]
        )


# ---------------------------------------------------------------------------
# Parity guard: sharded-vs-replicated TRAINING on the 1×8 host mesh
# ---------------------------------------------------------------------------


class TestTrainingParity1x8:
    def test_all_model_mesh_matches_unsharded(self):
        """The ISSUE 9 parity satellite: a 1×8 (data=1, model=8) mesh —
        factor tables fully sharded, no data parallelism — must train
        factors matching the single-device run, and serving top-K over
        the two models must return identical ids."""
        from predictionio_tpu.controller.context import mesh_context

        rng = np.random.default_rng(7)
        n = 500
        rows = rng.integers(0, 60, n).astype(np.int64)
        cols = rng.integers(0, 40, n).astype(np.int64)
        vals = rng.uniform(1, 5, n).astype(np.float32)
        cfg = ALSConfig(rank=4, iterations=4, seed=5)
        single = train_als(rows, cols, vals, 60, 40, cfg)
        ctx = mesh_context(axis_sizes=(1, 8))
        assert ctx.mesh.shape["model"] == 8
        sharded = train_als(rows, cols, vals, 60, 40, cfg, mesh=ctx.mesh)
        np.testing.assert_allclose(
            np.asarray(single.user), np.asarray(sharded.user),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(single.item), np.asarray(sharded.item),
            rtol=1e-4, atol=1e-5,
        )
        # serving top-K ids agree between the two trainings AND between
        # the sharded and replicated serving layouts of each
        mesh = sharding.serving_mesh()
        it_single = sharding.shard_table(np.asarray(single.item), mesh)
        ut_single = sharding.shard_table(np.asarray(single.user), mesh)
        idx = np.arange(16, dtype=np.int32)
        ids_shard, _ = sharding.sharded_topk_users(
            idx, ut_single, it_single, 8, 40, mesh
        )
        ids_repl, _ = top_k_items_batch(
            jnp.asarray(idx),
            jnp.asarray(np.asarray(single.user)),
            jnp.asarray(np.asarray(single.item)),
            8,
        )
        np.testing.assert_array_equal(
            np.asarray(ids_shard), np.asarray(ids_repl)
        )


# ---------------------------------------------------------------------------
# Template serving hooks
# ---------------------------------------------------------------------------


class TestServingHooks:
    def test_shard_then_predict_matches_pinned(self):
        uf, vf = _factors()
        algo = ALSAlgorithm(ALSAlgorithmParams())
        m_s, nbytes = algo.shard_model_for_serving(_model(uf, vf))
        m_p, _ = algo.pin_model_for_serving(_model(uf, vf))
        assert m_s._pio_shards is not None
        assert m_s._pio_shards.num_shards == 8
        assert nbytes >= uf.nbytes + vf.nbytes  # padding only adds
        for u in ("u0", "u13", "u69"):
            got = algo.predict(m_s, Query(user=u, num=7))
            want = algo.predict(m_p, Query(user=u, num=7))
            assert [s.item for s in got.item_scores] == [
                s.item for s in want.item_scores
            ]
        queries = [(j, Query(user=f"u{j % uf.shape[0]}", num=5)) for j in range(40)]
        got_b = dict(algo.batch_predict(m_s, queries))
        want_b = dict(algo.batch_predict(m_p, queries))
        for j in got_b:
            assert [s.item for s in got_b[j].item_scores] == [
                s.item for s in want_b[j].item_scores
            ]

    def test_per_device_memory_is_sharded_not_replicated(self):
        uf, vf = _factors(U=96, I=160, K=16)
        algo = ALSAlgorithm(ALSAlgorithmParams())
        m, _ = algo.shard_model_for_serving(_model(uf, vf))
        per_dev = sharding.per_device_bytes(
            m.user_factors
        ) + sharding.per_device_bytes(m.item_factors)
        repl = uf.nbytes + vf.nbytes
        assert per_dev <= repl / 8 * 1.1, (per_dev, repl)

    def test_release_restores_host_rows_and_drops_every_shard(self):
        """Satellite: the superseded generation's shard handles must die
        on EVERY device — the global array handle owns all per-device
        buffers, so it becoming unreferenced (weakref dead after gc)
        proves no stale per-device buffer stays registered."""
        uf, vf = _factors()
        algo = ALSAlgorithm(ALSAlgorithmParams())
        m, _ = algo.shard_model_for_serving(_model(uf, vf))
        old_user, old_item = m.user_factors, m.item_factors
        assert {s.device for s in old_user.addressable_shards} == set(
            jax.devices()
        )
        ref_u, ref_i = weakref.ref(old_user), weakref.ref(old_item)
        del old_user, old_item
        algo.release_pinned_model(m)
        assert m._pio_shards is None
        assert isinstance(m.user_factors, np.ndarray)
        assert m.user_factors.shape == uf.shape  # padding stripped
        np.testing.assert_array_equal(m.user_factors, uf)
        np.testing.assert_array_equal(m.item_factors, vf)
        gc.collect()
        assert ref_u() is None and ref_i() is None, (
            "released generation's sharded tables are still referenced — "
            "stale per-device buffers would accumulate per /reload"
        )

    def test_ann_sharded_matches_unsharded(self):
        from predictionio_tpu.serving.ann import AnnConfig

        uf, vf = _factors(U=40, I=400, K=16)
        algo = ALSAlgorithm(ALSAlgorithmParams())
        cfg = AnnConfig(enabled=True, nlist=13, nprobe=4, seed=1)
        m_s, _ = algo.shard_model_for_serving(_model(uf, vf))
        m_s, info_s = algo.build_ann_for_serving(m_s, cfg)
        m_p, _ = algo.pin_model_for_serving(_model(uf, vf))
        m_p, _info = algo.build_ann_for_serving(m_p, cfg)
        assert info_s["shards"] == 8
        assert m_s._pio_ann.shard_mesh is not None
        assert m_s._pio_ann.host_index is not None
        for u in ("u0", "u7", "u39"):
            got = algo.predict(m_s, Query(user=u, num=9))
            want = algo.predict(m_p, Query(user=u, num=9))
            assert [s.item for s in got.item_scores] == [
                s.item for s in want.item_scores
            ], u
        queries = [(j, Query(user=f"u{j % 40}", num=6)) for j in range(30)]
        got_b = dict(algo.batch_predict(m_s, queries))
        want_b = dict(algo.batch_predict(m_p, queries))
        for j in got_b:
            assert [s.item for s in got_b[j].item_scores] == [
                s.item for s in want_b[j].item_scores
            ]

    def test_ann_sharded_nprobe_eq_nlist_is_exact(self):
        """The bit-identity contract survives the sharded layout: with
        every cluster probed, sharded IVF == replicated exact batch."""
        from predictionio_tpu.ops import ivf

        mesh = sharding.serving_mesh()
        rng = np.random.default_rng(2)
        vf = rng.standard_normal((300, 8)).astype(np.float32)
        q = rng.standard_normal((16, 8)).astype(np.float32)
        index, info = ivf.build_ivf(vf, nlist=12, seed=0, iters=4)
        rt = ivf.AnnRuntime(index, nprobe=12, build_info=info)
        ivf.shard_runtime(rt, mesh)
        ids_s, sc_s = sharding.sharded_ivf_topk(
            jnp.asarray(q), rt.index, 10, 12, mesh
        )
        uidx = np.arange(16, dtype=np.int32)
        ids_e, sc_e = top_k_items_batch(uidx, jnp.asarray(q), jnp.asarray(vf), 10)
        np.testing.assert_array_equal(np.asarray(ids_s), np.asarray(ids_e))
        np.testing.assert_array_equal(np.asarray(sc_s), np.asarray(sc_e))

    def test_twotower_shard_hook_parity(self):
        from predictionio_tpu.templates.twotower.engine import (
            TwoTowerAlgorithm,
            TwoTowerParams,
            TwoTowerServingModel,
        )
        from predictionio_tpu.templates.twotower.engine import Query as TTQuery

        rng = np.random.default_rng(4)
        U, I, K = 30, 80, 8
        uv = rng.standard_normal((U, K)).astype(np.float32)
        iv = rng.standard_normal((I, K)).astype(np.float32)

        def mk():
            return TwoTowerServingModel(
                user_vecs=uv.copy(),
                item_vecs=iv.copy(),
                user_index=BiMap.string_index([f"u{i}" for i in range(U)]),
                item_index=BiMap.string_index([f"i{i}" for i in range(I)]),
                seen={},
                loss_history=(),
            )

        algo = TwoTowerAlgorithm(TwoTowerParams())
        m_s, _ = algo.shard_model_for_serving(mk())
        m_h = mk()  # host numpy path as the oracle
        assert m_s._pio_shards is not None
        for u in ("u0", "u7", "u29"):
            got = algo.predict(m_s, TTQuery(user=u, num=6))
            want = algo.predict(m_h, TTQuery(user=u, num=6))
            assert [s.item for s in got.item_scores] == [
                s.item for s in want.item_scores
            ], u
        algo.release_pinned_model(m_s)
        assert isinstance(m_s.user_vecs, np.ndarray)
        assert m_s.user_vecs.shape == (U, K)
        np.testing.assert_array_equal(m_s.user_vecs, uv)


# ---------------------------------------------------------------------------
# QueryService integration: reload hot-swap under --shard-factors
# ---------------------------------------------------------------------------


@pytest.fixture()
def trained_variant(memory_storage_env):
    from predictionio_tpu.controller import local_context
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.workflow import load_engine_variant, run_train

    Storage = memory_storage_env
    app_id = Storage.get_meta_data_apps().insert(App(id=0, name="shard-app"))
    rng = np.random.default_rng(5)
    Storage.get_p_events().write(
        (
            Event(
                event="rate",
                entity_type="user",
                entity_id=str(u),
                target_entity_type="item",
                target_entity_id=str(i),
                properties=DataMap({"rating": float((u + i) % 5 + 1)}),
            )
            for u, i in zip(rng.integers(0, 30, 800), rng.integers(0, 60, 800))
        ),
        app_id,
    )
    variant = load_engine_variant(
        {
            "id": "shard-eng",
            "version": "1",
            "engineFactory": "predictionio_tpu.templates."
            "recommendation:engine_factory",
            "datasource": {"params": {"appName": "shard-app"}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {
                        "rank": 8,
                        "numIterations": 2,
                        "lambda": 0.05,
                        "seed": 5,
                    },
                }
            ],
        }
    )
    run_train(variant, local_context())
    return Storage, variant


class TestQueryServiceSharded:
    def test_sharded_service_matches_plain_service(self, trained_variant):
        from predictionio_tpu.serving import CacheConfig
        from predictionio_tpu.workflow.serving import QueryService

        _, variant = trained_variant
        qs_plain = QueryService(variant)
        qs_shard = QueryService(
            variant, cache=CacheConfig(shard_factors=True)
        )
        assert qs_shard.status_json()["shardFactors"] is True
        assert qs_plain.status_json()["shardFactors"] is False
        assert qs_shard.stats_json()["cache"]["factorShards"] == 8
        for u in ("1", "7", "29"):
            body = {"user": u, "num": 5}
            got = qs_shard.dispatch("POST", "/queries.json", {}, body)
            want = qs_plain.dispatch("POST", "/queries.json", {}, body)
            assert got.status == want.status == 200
            assert [s["item"] for s in got.body["itemScores"]] == [
                s["item"] for s in want.body["itemScores"]
            ], u

    def test_reload_drops_previous_generation_shards(self, trained_variant):
        """Satellite: ``/reload`` under ``--shard-factors`` must leave
        no stale per-device buffers of the superseded generation —
        asserted via weakrefs on the old generation's sharded tables
        (the jax.Array handle owns every device's buffer)."""
        from predictionio_tpu.serving import CacheConfig
        from predictionio_tpu.workflow.serving import QueryService

        _, variant = trained_variant
        qs = QueryService(variant, cache=CacheConfig(shard_factors=True))
        (_algo, model0), = qs._algo_model_pairs
        assert model0._pio_shards is not None
        refs = [
            weakref.ref(model0.user_factors),
            weakref.ref(model0.item_factors),
        ]
        old_user_shape = model0.user_factors.shape
        r = qs.dispatch("POST", "/reload", {}, None)
        assert r.status == 200
        (_algo1, model1), = qs._algo_model_pairs
        assert model1 is not model0
        assert model1._pio_shards is not None  # new generation re-sharded
        # the released generation fell back to trimmed host arrays...
        assert model0._pio_shards is None
        assert isinstance(model0.user_factors, np.ndarray)
        assert model0.user_factors.shape[0] <= old_user_shape[0]
        # ...and its sharded tables are collectable on every device
        del model0
        gc.collect()
        assert all(r() is None for r in refs), (
            "previous generation's shard handles survive /reload — "
            "per-device memory would grow by one catalog per swap"
        )
        # the swapped-in generation still serves
        got = qs.dispatch(
            "POST", "/queries.json", {}, {"user": "1", "num": 4}
        )
        assert got.status == 200 and len(got.body["itemScores"]) == 4
