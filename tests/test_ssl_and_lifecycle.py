"""HTTPS on both servers (parity: common/SSLConfiguration.scala — one TLS
layer shared by the event and query servers) and the deploy lifecycle:
GET /stop, `pio undeploy`, and the stop hook wiring."""

import datetime as dt
import json
import ssl
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.api.http import make_ssl_context, start_background


@pytest.fixture(scope="module")
def cert_pair(tmp_path_factory):
    """Self-signed localhost cert — via the ``openssl`` binary (present on
    every CI/dev image this repo targets), falling back to the optional
    `cryptography` package, else skipping (TLS material is environment
    tooling, not code under test)."""
    import shutil
    import subprocess

    d = tmp_path_factory.mktemp("certs")
    cert_path = d / "server.crt"
    key_path = d / "server.key"
    if shutil.which("openssl"):
        try:
            subprocess.run(
                [
                    "openssl", "req", "-x509", "-newkey", "rsa:2048",
                    "-keyout", str(key_path), "-out", str(cert_path),
                    "-days", "1", "-nodes", "-subj", "/CN=localhost",
                    "-addext", "subjectAltName=DNS:localhost",
                ],
                check=True,
                capture_output=True,
            )
            return str(cert_path), str(key_path)
        except (subprocess.CalledProcessError, OSError):
            # LibreSSL / OpenSSL < 1.1.1 lack -addext; fall through to
            # the cryptography-package path rather than ERRORing tests
            pass
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        pytest.skip("neither openssl nor `cryptography` available")

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = dt.datetime.now(dt.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - dt.timedelta(minutes=5))
        .not_valid_after(now + dt.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost")]), critical=False
        )
        .sign(key, hashes.SHA256())
    )
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


def _client_ctx():
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return ctx


def _get(url, ctx=None, data=None, method=None):
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


class TestHTTPS:
    def test_event_server_over_https(self, cert_pair, memory_storage_env):
        from predictionio_tpu.api import EventService
        from predictionio_tpu.data.storage.base import App
        from predictionio_tpu.data.storage.base import AccessKey

        apps = memory_storage_env.get_meta_data_apps()
        app_id = apps.insert(App(id=0, name="sslapp"))
        memory_storage_env.get_meta_data_access_keys().insert(
            AccessKey(key="sslkey", appid=app_id, events=[])
        )
        memory_storage_env.get_l_events().init(app_id)
        server, _ = start_background(
            EventService().dispatch,
            ssl_context=make_ssl_context(*cert_pair),
        )
        try:
            port = server.server_address[1]
            status, body = _get(
                f"https://localhost:{port}/events.json?accessKey=sslkey",
                ctx=_client_ctx(),
                data=json.dumps(
                    {"event": "rate", "entityType": "user", "entityId": "1"}
                ).encode(),
            )
            assert status == 201 and body["eventId"]
            # plaintext against the TLS socket must fail
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://localhost:{port}/", timeout=5
                ).read()
        finally:
            server.shutdown()
            server.server_close()

    def test_query_server_over_https_with_stop(self, cert_pair, trained_variant):
        from predictionio_tpu.workflow.serving import QueryService

        qs = QueryService(trained_variant)
        server, thread = start_background(
            qs.dispatch, ssl_context=make_ssl_context(*cert_pair)
        )
        stopped = []
        qs.stop_server = lambda: stopped.append(True) or server.shutdown()
        port = server.server_address[1]
        try:
            status, body = _get(
                f"https://localhost:{port}/", ctx=_client_ctx()
            )
            assert status == 200 and body["status"] == "alive"
            assert "feedbackDropped" in body
            status, body = _get(
                f"https://localhost:{port}/stop", ctx=_client_ctx()
            )
            assert status == 200 and stopped
            thread.join(timeout=10)
            assert not thread.is_alive()
        finally:
            server.server_close()

    def test_ssl_context_from_env(self, cert_pair, monkeypatch):
        from predictionio_tpu.api.http import ssl_context_from_env

        monkeypatch.delenv("PIO_SSL_CERT", raising=False)
        monkeypatch.delenv("PIO_SSL_KEY", raising=False)
        assert ssl_context_from_env() is None
        monkeypatch.setenv("PIO_SSL_CERT", cert_pair[0])
        monkeypatch.setenv("PIO_SSL_KEY", cert_pair[1])
        assert isinstance(ssl_context_from_env(), ssl.SSLContext)


@pytest.fixture()
def trained_variant(memory_storage_env):
    """A tiny trained Recommendation engine ready to deploy."""
    import numpy as np

    from predictionio_tpu.controller import local_context
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.workflow import load_engine_variant, run_train

    app_id = memory_storage_env.get_meta_data_apps().insert(App(id=0, name="lcapp"))
    le = memory_storage_env.get_l_events()
    le.init(app_id)
    rng = np.random.default_rng(0)
    for _ in range(200):
        le.insert(
            Event(
                event="rate",
                entity_type="user",
                entity_id=str(rng.integers(0, 20)),
                target_entity_type="item",
                target_entity_id=str(rng.integers(0, 15)),
                properties=DataMap({"rating": float(rng.integers(1, 6))}),
            ),
            app_id,
        )
    variant = load_engine_variant(
        {
            "id": "lc-rec",
            "version": "1",
            "engineFactory": "predictionio_tpu.templates.recommendation:engine_factory",
            "datasource": {"params": {"appName": "lcapp"}},
            "algorithms": [
                {"name": "als", "params": {"rank": 4, "numIterations": 2, "lambda": 0.1}}
            ],
        }
    )
    run_train(variant, local_context())
    return variant


class TestLifecycle:
    def test_stop_without_hook_is_501(self, trained_variant):
        from predictionio_tpu.workflow.serving import QueryService

        qs = QueryService(trained_variant)
        resp = qs.dispatch("GET", "/stop", {})
        assert resp.status == 501

    def test_deploy_query_undeploy_roundtrip(self, trained_variant):
        """The full lifecycle over real HTTP: deploy -> query -> undeploy
        (`pio undeploy` = GET /stop) -> server actually exits."""
        from predictionio_tpu.tools import commands
        from predictionio_tpu.workflow.serving import QueryService

        qs = QueryService(trained_variant)
        server, thread = start_background(qs.dispatch)
        qs.stop_server = server.shutdown
        port = server.server_address[1]
        try:
            status, body = _get(
                f"http://127.0.0.1:{port}/queries.json",
                data=json.dumps({"user": "3", "num": 2}).encode(),
            )
            assert status == 200 and "itemScores" in body
            out = []
            commands.undeploy("127.0.0.1", port, out=out.append)
            assert "Undeployed" in out[0]
            thread.join(timeout=10)
            assert not thread.is_alive()
        finally:
            server.server_close()

    def test_undeploy_unreachable_raises(self):
        from predictionio_tpu.tools import commands

        with pytest.raises(RuntimeError, match="Could not reach"):
            commands.undeploy("127.0.0.1", 1, out=lambda _: None)

    def test_stop_token_gates_shutdown(self, trained_variant, tmp_path, monkeypatch):
        """With a stop token set (pio deploy always sets one), GET /stop
        without the token is 403 and the server stays up; `pio undeploy`
        reads the token file and succeeds (advisor r3 low finding)."""
        from predictionio_tpu.tools import commands
        from predictionio_tpu.workflow.serving import QueryService

        monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))
        qs = QueryService(trained_variant)
        server, thread = start_background(qs.dispatch)
        qs.stop_server = server.shutdown
        port = server.server_address[1]
        qs.stop_token = commands.write_stop_token(port)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{port}/stop")
            assert ei.value.code == 403
            assert thread.is_alive()
            # undeploy with a wrong token reports the refusal
            with pytest.raises(RuntimeError, match="refused to stop"):
                commands.undeploy(
                    "127.0.0.1", port, token="wrong", out=lambda _: None
                )
            # default path: token read back from the basedir file
            out = []
            commands.undeploy("127.0.0.1", port, out=out.append)
            assert "Undeployed" in out[0]
            thread.join(timeout=10)
            assert not thread.is_alive()
        finally:
            server.server_close()


class TestStorageServerBindGuard:
    def test_refuses_public_bind_without_secret(self, monkeypatch):
        from predictionio_tpu.tools.console import main

        monkeypatch.delenv("PIO_STORAGE_SERVER_SECRET", raising=False)
        with pytest.raises(SystemExit, match="refusing to bind"):
            main(["storageserver", "--ip", "0.0.0.0", "--port", "0"])
