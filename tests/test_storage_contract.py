"""Storage contract suite — ONE behavioral spec run against EVERY driver.

This is the reference's most important testing idea (SURVEY.md section 5.1:
``LEventsSpec``/``PEventsSpec`` parameterized over HBase/JDBC/ES), ported:
each fixture params over the available backends and the same assertions run
against each.
"""

import datetime as dt

import pytest

from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    Model,
    StorageClientConfig,
)
from predictionio_tpu.data.storage import (
    columnar,
    localfs,
    memory,
    remote,
    sharedfs,
    sqlite,
)

UTC = dt.timezone.utc
APP = 7


def _client(kind: str, tmp_path):
    """Returns (client, closer)."""
    if kind == "memory":
        c = memory.StorageClient(StorageClientConfig("T", "memory"))
        return c, c.close
    if kind == "columnar":
        c = columnar.StorageClient(
            StorageClientConfig(
                "C", "columnar",
                {"path": str(tmp_path / "cols"), "segment_rows": "4"},
            )
        )
        return c, c.close
    if kind == "sqlite":
        c = sqlite.StorageClient(
            StorageClientConfig("T", "sqlite", {"path": str(tmp_path / "t.db")})
        )
        return c, c.close
    if kind == "remote":
        # the networked tri-role backend: a live storage server (wrapping
        # sqlite) on a real socket, spoken to by the TYPE=remote driver —
        # the same spec must hold across the wire
        from predictionio_tpu.api.http import start_background

        backing = sqlite.StorageClient(
            StorageClientConfig("B", "sqlite", {"path": str(tmp_path / "b.db")})
        )
        server, _ = start_background(
            remote.StorageRpcService(client=backing).dispatch
        )
        c = remote.StorageClient(
            StorageClientConfig(
                "R", "remote",
                {"hosts": "127.0.0.1", "ports": str(server.server_address[1])},
            )
        )

        def closer():
            c.close()
            server.shutdown()
            server.server_close()
            backing.close()

        return c, closer
    raise AssertionError(kind)


@pytest.fixture(params=["memory", "sqlite", "remote"])
def client(request, tmp_path):
    c, closer = _client(request.param, tmp_path)
    yield c
    closer()


#: events-only spec additionally runs against the columnar driver (it has
#: no metadata role — like the reference's HBase source, it is an
#: EVENTDATA backend; segment_rows=4 forces multi-segment coverage)
@pytest.fixture(params=["memory", "sqlite", "remote", "columnar"])
def events_client(request, tmp_path):
    c, closer = _client(request.param, tmp_path)
    yield c
    closer()


def _ev(name="rate", entity="u1", target=None, t=0, props=None):
    return Event(
        event=name, entity_type="user", entity_id=entity,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=DataMap(props or {}),
        event_time=dt.datetime(2021, 6, 1, tzinfo=UTC) + dt.timedelta(seconds=t),
    )


class TestLEventsContract:
    def test_insert_get_delete(self, events_client):
        le = events_client.get_l_events()
        le.init(APP)
        eid = le.insert(_ev(props={"rating": 5.0}, target="i1"), APP)
        got = le.get(eid, APP)
        assert got is not None
        assert got.event_id == eid
        assert got.properties.get_as("rating", float) == 5.0
        assert got.target_entity_id == "i1"
        assert le.delete(eid, APP)
        assert le.get(eid, APP) is None
        assert not le.delete(eid, APP)

    def test_find_filters(self, events_client):
        le = events_client.get_l_events()
        le.init(APP)
        le.insert(_ev("view", "u1", target="i1", t=0), APP)
        le.insert(_ev("rate", "u1", target="i2", t=10), APP)
        le.insert(_ev("rate", "u2", target="i1", t=20), APP)

        assert len(list(le.find(APP))) == 3
        assert len(list(le.find(APP, event_names=["rate"]))) == 2
        assert len(list(le.find(APP, entity_id="u1"))) == 2
        assert len(list(le.find(APP, target_entity_type="item",
                                target_entity_id="i1"))) == 2
        base = dt.datetime(2021, 6, 1, tzinfo=UTC)
        assert len(list(le.find(APP, start_time=base + dt.timedelta(seconds=5)))) == 2
        assert len(list(le.find(APP, until_time=base + dt.timedelta(seconds=10)))) == 1
        # ordering + limit + reversed
        times = [e.event_time for e in le.find(APP)]
        assert times == sorted(times)
        newest = list(le.find(APP, limit=1, reversed=True))
        assert newest[0].entity_id == "u2"

    def test_channel_isolation(self, events_client):
        le = events_client.get_l_events()
        le.init(APP)
        le.init(APP, 3)
        le.insert(_ev("view", "u1"), APP)
        le.insert(_ev("buy", "u1"), APP, 3)
        assert [e.event for e in le.find(APP)] == ["view"]
        assert [e.event for e in le.find(APP, 3)] == ["buy"]
        assert le.remove(APP, 3)
        le.init(APP, 3)
        assert list(le.find(APP, 3)) == []

    def test_insert_batch(self, events_client):
        le = events_client.get_l_events()
        le.init(APP)
        ids = le.insert_batch([_ev(t=i) for i in range(5)], APP)
        assert len(ids) == len(set(ids)) == 5
        assert len(list(le.find(APP))) == 5


class TestPEventsContract:
    def test_write_find_shards(self, events_client):
        pe = events_client.get_p_events()
        pe.write([_ev("rate", f"u{i}", target=f"i{i}", t=i) for i in range(10)], APP)
        allev = list(pe.find(APP))
        assert len(allev) == 10
        shards = [list(pe.find(APP, shard_index=s, num_shards=3)) for s in range(3)]
        ids = sorted(e.entity_id for sh in shards for e in sh)
        assert ids == sorted(f"u{i}" for i in range(10))
        assert all(len(s) > 0 for s in shards)

    def test_delete_all(self, events_client):
        pe = events_client.get_p_events()
        pe.write([_ev(t=i) for i in range(3)], APP)
        pe.delete(APP)
        assert list(pe.find(APP)) == []


class TestMetadataContract:
    def test_apps(self, client):
        apps = client.get_apps()
        app_id = apps.insert(App(0, "myapp", "desc"))
        assert app_id
        assert apps.get(app_id).name == "myapp"
        assert apps.get_by_name("myapp").id == app_id
        assert apps.insert(App(0, "myapp")) is None  # unique name
        second = apps.insert(App(0, "other"))
        assert {a.name for a in apps.get_all()} == {"myapp", "other"}
        assert apps.update(App(app_id, "renamed", None))
        assert apps.get_by_name("renamed") is not None
        assert apps.delete(second)
        assert apps.get(second) is None

    def test_access_keys(self, client):
        keys = client.get_access_keys()
        k1 = keys.insert(AccessKey("", 1, ("rate", "view")))
        assert k1 and keys.get(k1).events == ("rate", "view")
        k2 = keys.insert(AccessKey("explicit-key", 2))
        assert k2 == "explicit-key"
        assert {k.key for k in keys.get_by_appid(1)} == {k1}
        assert keys.update(AccessKey(k1, 1, ()))
        assert keys.get(k1).events == ()
        assert keys.delete(k1) and keys.get(k1) is None

    def test_channels(self, client):
        ch = client.get_channels()
        c1 = ch.insert(Channel(0, "backtest", 1))
        assert c1 and ch.get(c1).name == "backtest"
        assert ch.insert(Channel(0, "backtest", 1)) is None  # dup per app
        assert ch.insert(Channel(0, "bad name!", 1)) is None  # invalid name
        c2 = ch.insert(Channel(0, "live", 1))
        assert [c.id for c in ch.get_by_appid(1)] == [c1, c2]
        assert ch.delete(c1) and ch.get(c1) is None

    def test_engine_instances(self, client):
        repo = client.get_engine_instances()
        t0 = dt.datetime(2022, 1, 1, tzinfo=UTC)

        def mk(i, status):
            return EngineInstance(
                id="", status=status, start_time=t0 + dt.timedelta(hours=i),
                end_time=t0 + dt.timedelta(hours=i + 1),
                engine_id="eng", engine_version="1", engine_variant="default",
                engine_factory="mod:fn", batch=f"b{i}",
                env={"K": "V"}, mesh_conf={"mesh": "2x4"},
                algorithms_params='[{"name":"als"}]',
            )

        i1 = repo.insert(mk(0, "COMPLETED"))
        i2 = repo.insert(mk(1, "COMPLETED"))
        repo.insert(mk(2, "FAILED"))
        assert repo.get(i1).env == {"K": "V"}
        assert repo.get(i1).mesh_conf == {"mesh": "2x4"}
        latest = repo.get_latest_completed("eng", "1", "default")
        assert latest.id == i2
        assert len(repo.get_completed("eng", "1", "default")) == 2
        assert repo.get_latest_completed("eng", "2", "default") is None
        upd = repo.get(i1).with_status("FAILED")
        assert repo.update(upd) and repo.get(i1).status == "FAILED"
        assert repo.delete(i1) and repo.get(i1) is None

    def test_models_blob(self, client, tmp_path):
        if type(client).__module__.endswith("sqlite"):
            models = client.get_models()
        else:
            models = client.get_models()
        blob = b"\x00\x01binary\xff" * 100
        models.insert(Model("inst1", blob))
        assert models.get("inst1").models == blob
        models.insert(Model("inst1", b"v2"))  # overwrite
        assert models.get("inst1").models == b"v2"
        assert models.delete("inst1") and models.get("inst1") is None


class TestFsModels:
    @pytest.fixture(params=["localfs", "sharedfs"])
    def fs_client(self, request, tmp_path):
        mod = {"localfs": localfs, "sharedfs": sharedfs}[request.param]
        return mod.StorageClient(
            StorageClientConfig(
                "FS", request.param, {"path": str(tmp_path / "m")}
            )
        )

    def test_blob_roundtrip(self, fs_client):
        c = fs_client
        blob = bytes(range(256)) * 10
        c.get_models().insert(Model("abc/def", blob))  # id gets sanitized
        assert c.get_models().get("abc/def").models == blob
        assert c.get_models().delete("abc/def")
        assert c.get_models().get("abc/def") is None

    def test_overwrite_and_missing(self, fs_client):
        m = fs_client.get_models()
        m.insert(Model("x", b"v1"))
        m.insert(Model("x", b"v2"))
        assert m.get("x").models == b"v2"
        assert m.get("nope") is None
        assert not m.delete("nope")


class TestReviewRegressions:
    def test_empty_event_names_matches_nothing(self, events_client):
        le = events_client.get_l_events()
        le.init(APP)
        le.insert(_ev("view"), APP)
        assert list(le.find(APP, event_names=[])) == []
        assert len(list(le.find(APP, event_names=None))) == 1

    def test_auto_id_skips_explicit_ids(self, client):
        apps = client.get_apps()
        a1 = apps.insert(App(0, "r1"))
        assert apps.insert(App(a1 + 1, "r2")) == a1 + 1
        a3 = apps.insert(App(0, "r3"))
        assert a3 is not None and a3 not in (a1, a1 + 1)

    def test_limit_zero_and_negative(self, events_client):
        le = events_client.get_l_events()
        le.init(APP)
        le.insert(_ev(), APP)
        assert list(le.find(APP, limit=0)) == []
        assert len(list(le.find(APP, limit=-1))) == 1  # negative = unbounded

    def test_update_to_duplicate_name_rejected(self, client):
        apps = client.get_apps()
        a1 = apps.insert(App(0, "n1"))
        apps.insert(App(0, "n2"))
        assert apps.update(App(a1, "n2", None)) is False

    def test_microsecond_roundtrip(self, events_client):
        le = events_client.get_l_events()
        le.init(APP)
        t = dt.datetime(2021, 6, 1, 12, 0, 0, 123456, tzinfo=UTC)
        eid = le.insert(Event(event="v", entity_type="u", entity_id="1",
                              event_time=t), APP)
        assert le.get(eid, APP).event_time == t
