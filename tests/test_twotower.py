"""Two-tower retrieval: op-level training (single device + (4,2) and
(2,4) data x model meshes — sharded embedding tables via the shard-local
gather), template end-to-end through the real workflow, and the
compiled-HLO proof that embedding tables never replicate."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.controller.context import mesh_context
from predictionio_tpu.ops.twotower import (
    TwoTowerConfig,
    sharded_embedding_lookup,
    train_two_tower,
)


def clustered_interactions(num_users=60, num_items=30, groups=3, seed=0):
    rng = np.random.default_rng(seed)
    rows, cols = [], []
    for u in range(num_users):
        g = u % groups
        for i in range(num_items):
            if i % groups == g and rng.random() < 0.7:
                rows.append(u)
                cols.append(i)
    return np.array(rows), np.array(cols)


def group_separation(model, num_users=60, num_items=30, groups=3):
    s = model.user_vecs @ model.item_vecs.T
    ing = np.mean(
        [s[u, i] for u in range(num_users) for i in range(num_items) if i % groups == u % groups]
    )
    outg = np.mean(
        [s[u, i] for u in range(num_users) for i in range(num_items) if i % groups != u % groups]
    )
    return float(ing), float(outg)


CFG = TwoTowerConfig(dim=16, batch_size=64, epochs=30, learning_rate=0.05, seed=1)


class TestShardedLookup:
    def test_matches_dense_gather(self):
        rng = np.random.default_rng(0)
        tbl = rng.normal(size=(24, 8)).astype(np.float32)
        ids = rng.integers(0, 24, 16).astype(np.int32)
        ctx = mesh_context(axis_sizes=(4, 2))
        from jax.sharding import NamedSharding, PartitionSpec

        tbl_d = jax.device_put(
            jnp.asarray(tbl), NamedSharding(ctx.mesh, PartitionSpec("model", None))
        )
        ids_d = jax.device_put(
            jnp.asarray(ids), NamedSharding(ctx.mesh, PartitionSpec("data"))
        )
        got = np.asarray(
            jax.jit(
                lambda t, i: sharded_embedding_lookup(t, i, ctx.mesh)
            )(tbl_d, ids_d)
        )
        np.testing.assert_allclose(got, tbl[ids], rtol=1e-6)

    def test_lookup_gradient_stays_sharded(self):
        """The VJP must scatter-add into the LOCAL shard — grads carry the
        table's model sharding instead of replicating."""
        ctx = mesh_context(axis_sizes=(4, 2))
        from jax.sharding import NamedSharding, PartitionSpec

        tbl = jax.device_put(
            jnp.ones((16, 4)), NamedSharding(ctx.mesh, PartitionSpec("model", None))
        )
        ids = jax.device_put(
            jnp.arange(8, dtype=jnp.int32),
            NamedSharding(ctx.mesh, PartitionSpec("data")),
        )

        def f(t):
            return sharded_embedding_lookup(t, ids, ctx.mesh).sum()

        g = jax.jit(jax.grad(f))(tbl)
        # is_equivalent_to, not spec ==: jax versions differ on whether
        # trailing-None axes are kept in the reported spec, and the
        # property under test is the LAYOUT (model-sharded rows, not
        # replicated), not the spec's spelling
        assert g.sharding.is_equivalent_to(
            NamedSharding(ctx.mesh, PartitionSpec("model", None)), g.ndim
        )
        np.testing.assert_allclose(
            np.asarray(g), np.vstack([np.ones((8, 4)), np.zeros((8, 4))])
        )


class TestTrainTwoTower:
    def test_learns_group_structure_single_device(self):
        rows, cols = clustered_interactions()
        m = train_two_tower(rows, cols, 60, 30, CFG)
        ing, outg = group_separation(m)
        assert ing > outg + 0.2, (ing, outg)
        assert m.loss_history[-1][1] < m.loss_history[0][1]

    def test_mesh_matches_single_device(self):
        # fp32 GEMMs here: the test pins SHARDING equivalence, and bf16
        # rounding (the default) amplifies benign reduction-order noise
        # past any tolerance that would still catch a real sharding bug
        cfg = dataclasses.replace(CFG, gemm_dtype="float32")
        rows, cols = clustered_interactions()
        single = train_two_tower(rows, cols, 60, 30, cfg)
        for sizes in ((4, 2), (2, 4)):
            ctx = mesh_context(axis_sizes=sizes)
            sharded = train_two_tower(rows, cols, 60, 30, cfg, mesh=ctx.mesh)
            np.testing.assert_allclose(
                single.user_vecs, sharded.user_vecs, rtol=1e-3, atol=1e-4
            )

    def test_tables_never_replicate_in_lookup_fwd_or_bwd(self):
        """Compiled-HLO check (same property the ALS sweep proves;
        VERDICT r2 item 10): neither the forward lookup nor its gradient
        materializes the full [N_pad, D] table on a device — only
        [N_pad/S, D] shards appear in the partitioned module."""
        from jax.sharding import NamedSharding, PartitionSpec

        ctx = mesh_context(axis_sizes=(2, 4))
        N, D, B = 512, 8, 32
        tbl = jax.device_put(
            jnp.ones((N, D)), NamedSharding(ctx.mesh, PartitionSpec("model", None))
        )
        ids = jax.device_put(
            jnp.zeros((B,), jnp.int32),
            NamedSharding(ctx.mesh, PartitionSpec("data")),
        )

        def fwd(t, i):
            return sharded_embedding_lookup(t, i, ctx.mesh).sum()

        for fn in (fwd, jax.grad(fwd)):
            txt = jax.jit(fn).lower(tbl, ids).compile().as_text()
            assert f"f32[{N},{D}]" not in txt, "full table materialized"
            assert f"f32[{N // 4},{D}]" in txt, "expected per-shard tensors"

    def test_empty_interactions_rejected(self):
        with pytest.raises(ValueError, match="at least one"):
            train_two_tower(np.zeros(0, np.int64), np.zeros(0, np.int64), 4, 3)

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError, match="out of range"):
            train_two_tower(np.array([5]), np.array([0]), 4, 3)


class TestTwoTowerTemplate:
    VARIANT = {
        "id": "tt",
        "version": "1",
        "engineFactory": "predictionio_tpu.templates.twotower:engine_factory",
        "datasource": {"params": {"appName": "ttapp", "eventNames": ["view"]}},
        "algorithms": [
            {
                "name": "twotower",
                "params": {
                    "embeddingDim": 16,
                    "batchSize": 64,
                    "epochs": 20,
                    "learningRate": 0.05,
                    "seed": 1,
                },
            }
        ],
    }

    def _ingest(self, Storage):
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.base import App

        app_id = Storage.get_meta_data_apps().insert(App(0, "ttapp"))
        le = Storage.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(0)
        for u in range(40):
            g = u % 2
            for i in range(20):
                if i % 2 == g and rng.random() < 0.7:
                    le.insert(
                        Event(
                            event="view",
                            entity_type="user",
                            entity_id=str(u),
                            target_entity_type="item",
                            target_entity_id=str(i),
                        ),
                        app_id,
                    )

    def test_end_to_end_on_mesh(self, memory_storage_env):
        """Train through the real workflow on the (4,2) mesh, deploy
        through QueryService, and get group-consistent recommendations
        that exclude seen items."""
        from predictionio_tpu.workflow import load_engine_variant, run_train
        from predictionio_tpu.workflow.serving import QueryService

        self._ingest(memory_storage_env)
        variant = load_engine_variant(self.VARIANT)
        ctx = mesh_context(axis_sizes=(4, 2))
        instance = run_train(variant, ctx)
        assert instance.status == "COMPLETED"
        qs = QueryService(variant)
        status, payload = qs.handle_query({"user": "2", "num": 5})
        assert status == 200
        items = [s["item"] for s in payload["itemScores"]]
        assert items, "no recommendations"
        # seen items are excluded
        model = qs._algo_model_pairs[0][1]
        seen = model.seen.get("2", set())
        assert not (set(items) & seen)
        # user 2 is group 0: every UNSEEN group-0 item must outrank the
        # out-group items (most group-0 items are already seen, so a
        # simple majority check would be vacuous)
        unseen_g0 = {str(i) for i in range(0, 20, 2) if str(i) not in seen}
        take = min(len(unseen_g0), len(items))
        assert set(items[:take]) <= unseen_g0, (items, unseen_g0)

    def test_eval_with_recall_at_k(self, memory_storage_env):
        """`pio eval` path: k-fold read_eval + RecallAtK produce a real
        leaderboard for the two-tower engine."""
        from predictionio_tpu.controller import local_context
        from predictionio_tpu.controller.evaluation import (
            EngineParamsGenerator,
            Evaluation,
        )
        from predictionio_tpu.templates.twotower import engine_factory
        from predictionio_tpu.templates.twotower.engine import RecallAtK
        from predictionio_tpu.workflow import load_engine_variant
        from predictionio_tpu.workflow.core import run_evaluation

        self._ingest(memory_storage_env)
        engine = engine_factory()
        variant = load_engine_variant(self.VARIANT)
        ep = variant.engine_params(engine)
        evaluation = Evaluation(engine=engine, metric=RecallAtK(5))
        generator = EngineParamsGenerator([ep])
        instance, result = run_evaluation(
            evaluation, generator, local_context()
        )
        assert instance.status == "EVALCOMPLETED"
        score = result.best_score.score
        # clustered data: a trained retriever must beat random recall
        # (5 random picks of 10 unseen-ish items per user)
        assert 0.0 < score <= 1.0
        assert "Recall@5" in result.leaderboard()


class TestNonToyScale:
    """VERDICT r3 weak #6: two-tower coverage beyond toy shapes — a
    planted-preference workload at 10^5 interactions, dim 64, asserting
    real retrieval quality and that the per-epoch shuffle stays on
    device (one upload of the interaction set, not one per epoch)."""

    def test_recall_beats_random_at_scale(self):
        nnz, num_users, num_items, rank_true = 120_000, 2_000, 1_000, 8
        rng = np.random.default_rng(3)
        tu = rng.normal(size=(num_users, rank_true)).astype(np.float32)
        tv = rng.normal(size=(num_items, rank_true)).astype(np.float32)
        users = rng.integers(0, num_users, nnz + 2_000)
        cand = rng.integers(0, num_items, (users.size, 16))
        sc = np.einsum("nk,nck->nc", tu[users], tv[cand])
        items = cand[np.arange(users.size), sc.argmax(1)]
        r_tr, c_tr = users[:nnz], items[:nnz]
        r_te, c_te = users[nnz:], items[nnz:]

        model = train_two_tower(
            r_tr, c_tr, num_users, num_items,
            TwoTowerConfig(dim=64, batch_size=2048, epochs=2,
                           learning_rate=0.05, seed=1),
        )
        s = model.user_vecs[r_te] @ model.item_vecs.T  # [probe, I]
        top10 = np.argpartition(s, -10, axis=1)[:, -10:]
        recall = float(np.mean((top10 == c_te[:, None]).any(axis=1)))
        random_baseline = 10.0 / num_items
        # the argmax-of-16-candidates task caps attainable recall well
        # below 1.0; ~9x random is what dim-64 training reaches here
        assert recall > 5 * random_baseline, (recall, random_baseline)
        # loss must actually decrease over the run
        hist = model.loss_history
        assert hist[-1][1] < hist[0][1] * 0.8, hist

    def test_epoch_shuffle_stays_on_device(self, monkeypatch):
        """The interaction set must be uploaded ONCE: per-epoch shuffles
        are device-side permutation gathers, not host re-uploads
        (VERDICT r3 weak #6 — a per-epoch full-dataset transfer stall)."""
        import predictionio_tpu.ops.twotower as tt

        uploads = []
        real_asarray = jnp.asarray

        def spy(x, *a, **kw):
            if isinstance(x, np.ndarray) and x.size >= 1_000:
                uploads.append(x.size)
            return real_asarray(x, *a, **kw)

        monkeypatch.setattr(tt.jnp, "asarray", spy)
        rng = np.random.default_rng(0)
        train_two_tower(
            rng.integers(0, 50, 4_000), rng.integers(0, 30, 4_000), 50, 30,
            TwoTowerConfig(dim=8, batch_size=512, epochs=4, seed=0),
        )
        # one upload per side (rows + cols), regardless of epoch count
        assert len(uploads) == 2, uploads
