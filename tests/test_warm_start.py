"""Warm retrain (`pio train --warm-start`): factor seeding from the
previous COMPLETED instance's model, convergence in fewer sweeps, and the
id-space alignment when the catalog shifts (VERDICT r3 next-round #8)."""

import numpy as np
import pytest

from predictionio_tpu.ops.als import ALSConfig, train_als


def _planted(num_users=300, num_items=120, rank=6, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.normal(size=(num_users, rank)).astype(np.float32),
        rng.normal(size=(num_items, rank)).astype(np.float32),
    )


def _sample(u, v, nnz, seed):
    """Ratings sampled from one planted low-rank model (so a perturbation
    adds CONSISTENT new observations, as new real events would)."""
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, u.shape[0], nnz).astype(np.int64)
    cols = rng.integers(0, v.shape[0], nnz).astype(np.int64)
    vals = np.einsum("nk,nk->n", u[rows], v[cols]).astype(np.float32)
    vals += rng.normal(scale=0.05, size=nnz).astype(np.float32)
    return rows, cols, vals


def _workload(nnz=30_000, num_users=300, num_items=120, seed=0, rank=6):
    u, v = _planted(num_users, num_items, rank, seed)
    return _sample(u, v, nnz, seed + 100)


def _rmse(f, rows, cols, vals):
    pred = np.einsum(
        "nk,nk->n", np.asarray(f.user)[rows], np.asarray(f.item)[cols]
    )
    return float(np.sqrt(np.mean((pred - vals) ** 2)))


class TestWarmConvergence:
    def test_warm_start_halves_sweeps(self):
        """On a perturbed dataset, a warm-started train must reach the
        cold run's final RMSE in at most HALF the sweeps (the VERDICT's
        acceptance bar for this feature)."""
        u, v = _planted(seed=1)
        rows, cols, vals = _sample(u, v, 30_000, seed=2)
        cfg = dict(rank=8, reg=0.05, seed=3)
        base = train_als(
            rows, cols, vals, 300, 120, ALSConfig(iterations=8, **cfg)
        )
        # perturb: 2% NEW observations of the same underlying preferences
        r2, c2, v2 = _sample(u, v, 600, seed=9)
        rows_p = np.concatenate([rows, r2])
        cols_p = np.concatenate([cols, c2])
        vals_p = np.concatenate([vals, v2])

        cold_sweeps = 8
        cold = train_als(
            rows_p, cols_p, vals_p, 300, 120,
            ALSConfig(iterations=cold_sweeps, **cfg),
        )
        cold_rmse = _rmse(cold, rows_p, cols_p, vals_p)

        warm = train_als(
            rows_p, cols_p, vals_p, 300, 120,
            ALSConfig(iterations=cold_sweeps // 2, **cfg),
            init_user=np.asarray(base.user),
            init_item=np.asarray(base.item),
        )
        warm_rmse = _rmse(warm, rows_p, cols_p, vals_p)
        assert warm_rmse <= cold_rmse * 1.02, (warm_rmse, cold_rmse)

    def test_bad_init_shape_rejected(self):
        rows, cols, vals = _workload(nnz=500, num_users=50, num_items=20)
        with pytest.raises(ValueError, match="warm init"):
            train_als(
                rows, cols, vals, 50, 20, ALSConfig(iterations=1),
                init_user=np.zeros((49, 10), np.float32),
            )


class TestWorkflowWarmStart:
    @pytest.fixture()
    def app(self, memory_storage_env):
        from predictionio_tpu.data.event import DataMap, Event
        from predictionio_tpu.data.storage.base import App

        app_id = memory_storage_env.get_meta_data_apps().insert(
            App(id=0, name="warmapp")
        )
        le = memory_storage_env.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(4)
        for _ in range(400):
            le.insert(
                Event(
                    event="rate",
                    entity_type="user",
                    entity_id=f"u{rng.integers(0, 30)}",
                    target_entity_type="item",
                    target_entity_id=f"i{rng.integers(0, 20)}",
                    properties=DataMap({"rating": float(rng.integers(1, 6))}),
                ),
                app_id,
            )
        return app_id

    def _variant(self, iters):
        from predictionio_tpu.workflow import load_engine_variant

        return load_engine_variant(
            {
                "id": "warm-rec",
                "version": "1",
                "engineFactory": "predictionio_tpu.templates.recommendation:engine_factory",
                "datasource": {"params": {"appName": "warmapp"}},
                "algorithms": [
                    {
                        "name": "als",
                        "params": {
                            "rank": 6,
                            "numIterations": iters,
                            "lambda": 0.1,
                            "seed": 5,
                        },
                    }
                ],
            }
        )

    def test_warm_start_runs_and_records_lineage(self, app, memory_storage_env):
        """Cold train -> new events arrive (incl. NEW entities) -> warm
        retrain completes, records warm_start_from, and its model carries
        the previous factors (the carried rows differ from a cold init)."""
        from predictionio_tpu.controller import local_context
        from predictionio_tpu.data.event import DataMap, Event
        from predictionio_tpu.workflow import run_train
        from predictionio_tpu.workflow.core import WorkflowParams

        cold = run_train(self._variant(4), local_context())
        assert cold.status == "COMPLETED"

        le = memory_storage_env.get_l_events()
        for uid, iid in [("u999", "i3"), ("u1", "i999")]:  # new entities
            le.insert(
                Event(
                    event="rate", entity_type="user", entity_id=uid,
                    target_entity_type="item", target_entity_id=iid,
                    properties=DataMap({"rating": 5.0}),
                ),
                app,
            )
        warm = run_train(
            self._variant(2),
            local_context(),
            WorkflowParams(warm_start=True),
        )
        assert warm.status == "COMPLETED"
        assert warm.env.get("warm_start_from") == cold.id
        # deployability: the warm model answers queries incl. new entities
        from predictionio_tpu.workflow.serving import QueryService

        # instance_id pins the WARM instance explicitly (the latest-
        # COMPLETED default would also be warm here, but the pin keeps
        # the assertion meaningful if more trains are added above)
        qs = QueryService(self._variant(2), instance_id=warm.id)
        resp = qs.dispatch(
            "POST", "/queries.json", {}, {"user": "u999", "num": 3}
        )
        assert resp.status == 200 and resp.body["itemScores"]

    def test_warm_start_without_predecessor_falls_back(self, app):
        from predictionio_tpu.controller import local_context
        from predictionio_tpu.workflow import run_train
        from predictionio_tpu.workflow.core import WorkflowParams

        inst = run_train(
            self._variant(2), local_context(), WorkflowParams(warm_start=True)
        )
        assert inst.status == "COMPLETED"
        assert "warm_start_from" not in inst.env


class TestTwoTowerWarmStart:
    @pytest.fixture()
    def tt_app(self, memory_storage_env):
        from predictionio_tpu.data.event import DataMap, Event
        from predictionio_tpu.data.storage.base import App

        app_id = memory_storage_env.get_meta_data_apps().insert(
            App(id=0, name="ttwarm")
        )
        le = memory_storage_env.get_l_events()
        le.init(app_id)
        rng = np.random.default_rng(7)
        for _ in range(600):
            u = int(rng.integers(0, 40))
            # two taste clusters so the towers learn real structure
            i = int(rng.integers(0, 15)) + (u % 2) * 15
            le.insert(
                Event(
                    event="buy", entity_type="user", entity_id=f"u{u}",
                    target_entity_type="item", target_entity_id=f"i{i}",
                    properties=DataMap({}),
                ),
                app_id,
            )
        return app_id

    def _variant(self, epochs):
        from predictionio_tpu.workflow import load_engine_variant

        return load_engine_variant(
            {
                "id": "warm-tt",
                "version": "1",
                "engineFactory": "predictionio_tpu.templates.twotower:engine_factory",
                "datasource": {"params": {"appName": "ttwarm"}},
                "algorithms": [
                    {
                        "name": "twotower",
                        "params": {
                            "embeddingDim": 8,
                            "epochs": epochs,
                            "batchSize": 128,
                            "seed": 2,
                        },
                    }
                ],
            }
        )

    def test_warm_retrain_carries_embeddings_and_improves_start(
        self, tt_app, memory_storage_env
    ):
        """Warm two-tower retrain: lineage recorded, embeddings carried
        (first-epoch loss starts below the cold run's first-epoch loss),
        and new entities still served."""
        from predictionio_tpu.controller import local_context
        from predictionio_tpu.data.event import DataMap, Event
        from predictionio_tpu.workflow import run_train
        from predictionio_tpu.workflow.core import WorkflowParams

        cold = run_train(self._variant(6), local_context())
        assert cold.status == "COMPLETED"

        le = memory_storage_env.get_l_events()
        le.insert(
            Event(
                event="buy", entity_type="user", entity_id="u999",
                target_entity_type="item", target_entity_id="i3",
                properties=DataMap({}),
            ),
            tt_app,
        )
        warm = run_train(
            self._variant(2), local_context(), WorkflowParams(warm_start=True)
        )
        assert warm.status == "COMPLETED"
        assert warm.env.get("warm_start_from") == cold.id

        # compare first-logged losses: the warm run must start from a
        # materially better point than a cold run of the same shape
        cold2 = run_train(self._variant(2), local_context())
        from predictionio_tpu.data.storage import Storage

        def first_loss(inst):
            variant = self._variant(2)
            engine = variant.build_engine()
            ep = variant.engine_params(engine)
            blob = Storage.get_model_data_models().get(inst.id).models
            models = engine.models_from_bytes(ep, inst.id, blob)
            return models[0][1].loss_history[0][1]

        assert first_loss(warm) < first_loss(cold2) * 0.9, (
            first_loss(warm), first_loss(cold2)
        )

        from predictionio_tpu.workflow.serving import QueryService

        # instance_id pins the WARM model — the latest COMPLETED
        # instance is cold2 (trained after warm), which would otherwise
        # answer and make this assertion vacuous for the warm path
        qs = QueryService(self._variant(2), instance_id=warm.id)
        resp = qs.dispatch(
            "POST", "/queries.json", {}, {"user": "u999", "num": 3}
        )
        assert resp.status == 200 and resp.body["itemScores"]
