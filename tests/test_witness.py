"""Runtime lock-witness sanitizer (predictionio_tpu.analysis.witness) —
ISSUE 8.

The witness is the dynamic half of the concurrency story: these tests
seed real executions — including a two-lock deadlock pattern — and
assert the witness sees exactly what happened: the acquisition-order
digraph, the inversion, hold-time percentiles, sleeps under a lock, and
the CONFIRMED/PLAUSIBLE join against the static PIO207 cycle set.

Fixture locks are allocated from a scratch module written under the
witness's ``root`` (the witness only wraps repo-allocated locks — that
scoping is itself under test).
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from predictionio_tpu.analysis.witness import (
    LockWitness,
    classify_static_cycles,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PAIR_MODULE = """\
import threading
import time


class Pair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.RLock()


def ab(p, sleep_s=0.0):
    with p._a_lock:
        with p._b_lock:
            if sleep_s:
                time.sleep(sleep_s)


def ba(p):
    with p._b_lock:
        with p._a_lock:
            pass
"""


def _load_scratch(tmp_path, name="witness_pair", source=_PAIR_MODULE):
    path = os.path.join(str(tmp_path), f"{name}.py")
    with open(path, "w") as fh:
        fh.write(textwrap.dedent(source))
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def witness(tmp_path):
    w = LockWitness(root=str(tmp_path), long_hold_ms=20.0)
    w.install()
    yield w
    w.uninstall()


def test_witness_reports_seeded_two_lock_deadlock(tmp_path, witness):
    """The acceptance fixture: two threads acquire the same two locks in
    opposite orders (sequenced so the run itself cannot deadlock). The
    witness must report the inversion — the runtime proof that one
    unlucky schedule away lies a real deadlock."""
    mod = _load_scratch(tmp_path)
    p = mod.Pair()
    t1 = threading.Thread(target=mod.ab, args=(p, 0.03), daemon=True)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=mod.ba, args=(p,), daemon=True)
    t2.start()
    t2.join()
    rep = witness.report()
    assert set(rep["locks"]) == {"Pair._a_lock", "Pair._b_lock"}
    edge_pairs = {(e["from"], e["to"]) for e in rep["edges"]}
    assert ("Pair._a_lock", "Pair._b_lock") in edge_pairs
    assert ("Pair._b_lock", "Pair._a_lock") in edge_pairs
    assert len(rep["inversions"]) == 1
    cyc = rep["inversions"][0]["cycle"]
    assert cyc[0] == cyc[-1]
    assert set(cyc) == {"Pair._a_lock", "Pair._b_lock"}
    # hold-time percentiles + the long-hold counter saw the 30 ms hold
    a = rep["locks"]["Pair._a_lock"]
    assert a["acquisitions"] == 2
    assert a["holdMs"]["max"] >= 20.0
    assert a["longHolds"] >= 1
    # the sleep happened while holding _b_lock (innermost): witnessed
    sleeps = {s["lock"]: s for s in rep["sleepsUnderLock"]}
    assert "Pair._b_lock" in sleeps
    assert sleeps["Pair._b_lock"]["seconds"] >= 0.03


def test_consistent_order_reports_no_inversion(tmp_path, witness):
    mod = _load_scratch(tmp_path)
    p = mod.Pair()
    for _ in range(3):
        mod.ab(p)
    rep = witness.report()
    assert rep["inversions"] == []
    edge = [e for e in rep["edges"]
            if (e["from"], e["to"]) == ("Pair._a_lock", "Pair._b_lock")]
    assert edge and edge[0]["count"] == 3


def test_witness_only_wraps_repo_allocated_locks(tmp_path, witness):
    """Locks allocated outside the witness root (stdlib internals,
    site-packages, other checkouts) stay raw — the digraph carries only
    repo lock sites, with no phantom nodes from Thread/Event internals."""
    mod = _load_scratch(tmp_path)
    p = mod.Pair()
    # stdlib allocation on a repo object's behalf: Event -> Condition
    ev = threading.Event()
    t = threading.Thread(target=lambda: (mod.ab(p), ev.set()), daemon=True)
    t.start()
    ev.wait(5.0)
    t.join(5.0)
    rep = witness.report()
    assert set(rep["locks"]) == {"Pair._a_lock", "Pair._b_lock"}
    assert threading.Lock is not type(p._a_lock)  # wrapped, not raw


def test_wrappers_are_drop_in(tmp_path, witness):
    """The wrappers must be behaviorally invisible: try-acquire with
    timeout, locked(), RLock reentrancy, and Condition over a witnessed
    RLock (wait/notify releases and restores the held bookkeeping)."""
    mod = _load_scratch(tmp_path)
    p = mod.Pair()
    # non-blocking + timeout acquire on the Lock wrapper
    assert p._a_lock.acquire(False) is True
    assert p._a_lock.locked()
    got = []
    t = threading.Thread(
        target=lambda: got.append(p._a_lock.acquire(True, 0.05)), daemon=True
    )
    t.start()
    t.join()
    assert got == [False]  # contended try-acquire timed out cleanly
    p._a_lock.release()
    # RLock reentrancy through the wrapper
    with p._b_lock:
        with p._b_lock:
            pass
    # Condition over the witnessed RLock: wait() must not deadlock and
    # must restore the lock (and the witness's held-stack) on wake
    cond = threading.Condition(p._b_lock)
    woke = []

    def waiter():
        with cond:
            woke.append(cond.wait(timeout=5.0))

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(5.0)
    assert woke == [True]
    rep = witness.report()
    # reentrant acquire counted once per outermost hold
    assert rep["locks"]["Pair._b_lock"]["acquisitions"] >= 2
    assert rep["inversions"] == []


def test_uninstall_restores_factories(tmp_path):
    real_lock, real_rlock, real_sleep = (
        threading.Lock, threading.RLock, time.sleep
    )
    w = LockWitness(root=str(tmp_path))
    w.install()
    assert threading.Lock is not real_lock
    w.uninstall()
    assert threading.Lock is real_lock
    assert threading.RLock is real_rlock
    assert time.sleep is real_sleep


def test_cross_thread_release_leaves_no_phantom_edge(tmp_path, witness):
    """A plain Lock may legally be released by a thread other than the
    acquirer (handoff). The acquirer's held-stack entry must be retired
    by that release: a later acquisition on the acquiring thread must
    NOT record a phantom `handoff -> other` ordering edge (which could
    flip CI red with a false inversion), and the handoff hold time must
    still land in the stats."""
    mod = _load_scratch(
        tmp_path,
        "witness_handoff",
        """\
        import threading

        class H:
            def __init__(self):
                self._handoff_lock = threading.Lock()
                self._other_lock = threading.Lock()
        """,
    )
    h = mod.H()
    h._handoff_lock.acquire()  # main thread acquires...
    t = threading.Thread(target=h._handoff_lock.release, daemon=True)
    t.start()
    t.join()  # ...a worker releases it
    with h._other_lock:  # nothing is held here: no edge
        pass
    rep = witness.report()
    pairs = {(e["from"], e["to"]) for e in rep["edges"]}
    assert ("H._handoff_lock", "H._other_lock") not in pairs
    assert rep["inversions"] == []
    # the cross-thread release still closed the hold-time sample
    assert rep["locks"]["H._handoff_lock"]["holdMs"]["max"] is not None


def test_classify_ambiguous_short_names_stay_plausible():
    """Two static lock ids that truncate to the same witness site name
    (same-named classes in different modules) cannot CONFIRM each
    other's cycles — a witnessed edge on the colliding name proves
    nothing about which module's lock was involved."""
    colliding = [
        {
            "cycle": [
                "predictionio_tpu.m1.Runner._lock",
                "predictionio_tpu.m1.Other._b_lock",
                "predictionio_tpu.m1.Runner._lock",
            ],
        },
        {
            "cycle": [
                "predictionio_tpu.m2.Runner._lock",
                "predictionio_tpu.m2.Other._b_lock",
                "predictionio_tpu.m2.Runner._lock",
            ],
        },
    ]
    rep = {
        "edges": [
            {"from": "Runner._lock", "to": "Other._b_lock", "count": 1},
            {"from": "Other._b_lock", "to": "Runner._lock", "count": 1},
        ]
    }
    out = classify_static_cycles(colliding, rep)
    assert [c["status"] for c in out] == ["PLAUSIBLE", "PLAUSIBLE"]


def test_nested_uninstall_restores_outer_witness(tmp_path):
    """A nested install/uninstall (the `pytest --lock-witness` session
    witness around test_witness's own fixtures, or `run_with_witness`
    under `pio tsan`) must hand back the OUTER witness's factories, not
    the real ones — otherwise the outer witness keeps installed=True
    while recording nothing, and its inversion gate passes blind."""
    real_lock = threading.Lock
    outer = LockWitness(root=str(tmp_path))
    outer.install()
    outer_factory = threading.Lock
    inner = LockWitness(root=str(tmp_path))
    inner.install()
    assert threading.Lock is not outer_factory
    inner.uninstall()
    # the outer witness is live again — not silently un-patched
    assert outer.installed
    assert threading.Lock is outer_factory
    mod = _load_scratch(tmp_path, "witness_nested")
    p = mod.Pair()
    mod.ab(p)
    assert "Pair._a_lock" in outer.report()["locks"]
    outer.uninstall()
    assert threading.Lock is real_lock


# ---------------------------------------------------------------------------
# CONFIRMED vs PLAUSIBLE: the static-cycle join
# ---------------------------------------------------------------------------

_STATIC_CYCLE = [
    {
        "cycle": [
            "predictionio_tpu.m1.A._a_lock",
            "predictionio_tpu.m2.Other._b_lock",
            "predictionio_tpu.m1.A._a_lock",
        ],
        "edges": [],
        "lexical_only": False,
        "modules": ["predictionio_tpu/m1.py", "predictionio_tpu/m2.py"],
    }
]


def test_classify_confirmed_when_every_edge_witnessed():
    rep = {
        "edges": [
            {"from": "A._a_lock", "to": "Other._b_lock", "count": 4},
            {"from": "Other._b_lock", "to": "A._a_lock", "count": 1},
        ]
    }
    out = classify_static_cycles(_STATIC_CYCLE, rep)
    assert out[0]["status"] == "CONFIRMED"
    assert out[0]["witnessedEdges"] == out[0]["totalEdges"] == 2


def test_classify_plausible_when_partially_or_never_witnessed():
    partial = {
        "edges": [{"from": "A._a_lock", "to": "Other._b_lock", "count": 4}]
    }
    out = classify_static_cycles(_STATIC_CYCLE, partial)
    assert out[0]["status"] == "PLAUSIBLE"
    assert out[0]["witnessedEdges"] == 1
    out = classify_static_cycles(_STATIC_CYCLE, {"edges": []})
    assert out[0]["status"] == "PLAUSIBLE"
    assert out[0]["witnessedEdges"] == 0


def test_end_to_end_static_cycle_confirmed_by_execution(tmp_path):
    """The full loop: piolint finds a cross-module PIO207 cycle in
    fixture sources; executing the equivalent lock pattern under the
    witness CONFIRMS it."""
    import textwrap as tw

    from predictionio_tpu.analysis.callgraph import (
        ProgramContext,
        build_callgraph,
    )
    from predictionio_tpu.analysis.engine import FileContext
    from predictionio_tpu.analysis.manifest import DEFAULT_MANIFEST
    from predictionio_tpu.analysis.rules_program import lock_order_cycles

    m1 = """\
    import threading
    from predictionio_tpu.m2 import Other

    class A:
        def __init__(self):
            self._a_lock = threading.Lock()
            self.other = Other()

        def one(self):
            with self._a_lock:
                self.other.poke()

        def fold_hot_rows(self):
            with self._a_lock:
                pass
    """
    m2 = """\
    import threading

    class Other:
        def __init__(self, owner=None):
            self._b_lock = threading.Lock()
            self.owner = owner

        def poke(self):
            with self._b_lock:
                pass

        def two(self):
            with self._b_lock:
                self.owner.fold_hot_rows()
    """
    contexts = {
        "predictionio_tpu/m1.py": FileContext(
            "predictionio_tpu/m1.py", tw.dedent(m1), DEFAULT_MANIFEST
        ),
        "predictionio_tpu/m2.py": FileContext(
            "predictionio_tpu/m2.py", tw.dedent(m2), DEFAULT_MANIFEST
        ),
    }
    cycles = lock_order_cycles(
        ProgramContext(contexts, build_callgraph(contexts))
    )
    assert len(cycles) == 1

    runnable = """\
    import threading

    class Other:
        def __init__(self, owner=None):
            self._b_lock = threading.Lock()
            self.owner = owner

        def poke(self):
            with self._b_lock:
                pass

        def two(self):
            with self._b_lock:
                self.owner.fold_hot_rows()

    class A:
        def __init__(self):
            self._a_lock = threading.Lock()
            self.other = Other(self)

        def one(self):
            with self._a_lock:
                self.other.poke()

        def fold_hot_rows(self):
            with self._a_lock:
                pass
    """
    w = LockWitness(root=str(tmp_path))
    w.install()
    try:
        mod = _load_scratch(tmp_path, "witness_cycle", runnable)
        a = mod.A()
        a.one()
        a.other.two()
    finally:
        w.uninstall()
    out = classify_static_cycles(cycles, w.report())
    assert [c["status"] for c in out] == ["CONFIRMED"]
    # without the reverse path the same cycle is only PLAUSIBLE
    w2 = LockWitness(root=str(tmp_path))
    w2.install()
    try:
        mod = _load_scratch(tmp_path, "witness_cycle2", runnable)
        a = mod.A()
        a.one()
    finally:
        w2.uninstall()
    out = classify_static_cycles(cycles, w2.report())
    assert [c["status"] for c in out] == ["PLAUSIBLE"]


# ---------------------------------------------------------------------------
# pio tsan CLI
# ---------------------------------------------------------------------------


def test_pio_tsan_cli_smoke(tmp_path):
    """`pio tsan -- version` runs the nested command under the witness
    and emits the joined report (ok, staticLockCycles classified)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    report_path = str(tmp_path / "tsan.json")
    proc = subprocess.run(
        [
            sys.executable, "-m", "predictionio_tpu.tools.console",
            "tsan", "--report", report_path, "--", "version",
        ],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.load(open(report_path))
    assert rec["ok"] is True
    assert rec["exitCode"] == 0
    assert rec["command"] == ["version"]
    assert rec["witness"]["inversions"] == []
    # every static cycle (the tree currently has none — this asserts the
    # contract either way) is classified
    for cyc in rec["staticLockCycles"]:
        assert cyc["status"] in ("CONFIRMED", "PLAUSIBLE")
