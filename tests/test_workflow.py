"""Workflow runtime tests: engine.json loading, run_train lineage +
model persistence, run_evaluation records. Uses the fake-DASE engine and
the in-memory storage fixture."""

import json

import pytest

from predictionio_tpu.controller import (
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    AverageMetric,
    FirstServing,
    local_context,
)
from predictionio_tpu.workflow import (
    WorkflowParams,
    load_engine_variant,
    run_evaluation,
    run_train,
)

from fake_dase import AlgoParams, DSParams, engine0, simple_params

VARIANT = {
    "id": "fake-engine",
    "version": "0.1",
    "description": "fake DASE engine",
    "engineFactory": "fake_dase:engine0",
    "datasource": {"params": {"base": 10}},
    "algorithms": [
        {"name": "a0", "params": {"mult": 2}},
        {"name": "a1", "params": {"mult": 3}},
    ],
}


class TestEngineVariant:
    def test_load_from_obj(self):
        v = load_engine_variant(VARIANT)
        assert v.id == "fake-engine"
        eng = v.build_engine()
        ep = v.engine_params(eng)
        assert ep.datasource == DSParams(base=10)
        assert ep.algorithms == (("a0", AlgoParams(2)), ("a1", AlgoParams(3)))

    def test_load_from_file(self, tmp_path):
        p = tmp_path / "engine.json"
        p.write_text(json.dumps(VARIANT))
        v = load_engine_variant(str(p))
        assert v.engine_factory == "fake_dase:engine0"

    def test_missing_factory_raises(self):
        with pytest.raises(ValueError, match="engineFactory"):
            load_engine_variant({"id": "x"})

    def test_missing_file_raises(self):
        with pytest.raises(FileNotFoundError):
            load_engine_variant("/nonexistent/engine.json")


class TestRunTrain:
    def test_completed_instance_and_model_blob(self, memory_storage_env):
        Storage = memory_storage_env
        variant = load_engine_variant(VARIANT)
        instance = run_train(variant, local_context(), WorkflowParams(batch="b1"))
        assert instance.status == "COMPLETED"
        assert instance.batch == "b1"
        assert instance.engine_factory == "fake_dase:engine0"
        # params recorded for reproducibility
        assert json.loads(instance.algorithms_params)[0] == {
            "name": "a0", "params": {"mult": 2}
        }
        # model blob persisted under the instance id
        blob = Storage.get_model_data_models().get(instance.id)
        assert blob is not None and len(blob.models) > 0
        # metadata repo agrees
        got = Storage.get_meta_data_engine_instances().get_latest_completed(
            "fake-engine", "0.1", "fake-engine"
        )
        assert got is not None and got.id == instance.id

    def test_failed_instance_on_error(self, memory_storage_env, monkeypatch):
        Storage = memory_storage_env

        class Boom(Exception):
            pass

        import fake_dase

        class BoomAlgo(fake_dase.Algo0):
            def train(self, ctx, pd):
                raise Boom("train exploded")

        def boom_engine():
            eng = engine0()
            eng.algorithms_class_map = {"a0": BoomAlgo, "a1": BoomAlgo}
            return eng

        monkeypatch.setattr(fake_dase, "engine0", boom_engine)
        with pytest.raises(Boom):
            run_train(load_engine_variant(VARIANT), local_context())
        all_instances = Storage.get_meta_data_engine_instances().get_all()
        assert any(i.status == "FAILED" for i in all_instances)

    def test_stop_after_read(self, memory_storage_env):
        Storage = memory_storage_env
        instance = run_train(
            load_engine_variant(VARIANT), local_context(),
            WorkflowParams(stop_after_read=True),
        )
        assert instance.status == "STOPPED"
        assert Storage.get_model_data_models().get(instance.id) is None


class MAE(AverageMetric):
    def calculate_unit(self, q, p, a):
        return -abs(p - a)


class TestRunEvaluation:
    def test_records_evaluation_instance(self, memory_storage_env):
        Storage = memory_storage_env
        eng = engine0()
        eng.serving_class = FirstServing
        candidates = [
            EngineParams(datasource=DSParams(), algorithms=(("a0", AlgoParams(mult=5)),)),
            EngineParams(datasource=DSParams(), algorithms=(("a0", AlgoParams(mult=1)),)),
        ]
        evaluation = Evaluation(engine=eng, metric=MAE())
        generator = EngineParamsGenerator(candidates)
        instance, result = run_evaluation(evaluation, generator, local_context())
        assert instance.status == "EVALCOMPLETED"
        assert result.best_index == 1
        stored = Storage.get_meta_data_evaluation_instances().get(instance.id)
        assert stored.status == "EVALCOMPLETED"
        parsed = json.loads(stored.evaluator_results_json)
        assert parsed["bestIdx"] == 1
        assert "BEST" in stored.evaluator_results
